"""Dispatch-hygiene rules: the device queue must never drain.

``host-sync-in-dispatch``: on TPU the engine's throughput is the device
queue's occupancy (PAPERS.md: "Exploring the limits of Concurrency in ML
Training on Google TPUs"); one stray ``.item()`` / ``device_get`` /
``np.asarray`` on a device value inside the scheduler's dispatch path
serializes host and device and re-introduces the per-token round trip
the dispatch-ahead pipeline exists to hide.  The rule builds the
intra-file call graph from every ``*Engine`` class's scheduler roots
(``_loop``/``_admit``/``_process``...) and flags host-materialization
calls in anything reachable — and, on the same reachability, blocking
SOCKET I/O (``sendall``/``recv``/``create_connection``, ISSUE 8): live
KV migration streams block bytes between replicas, and a socket send on
the scheduler thread would stall every live request for a network round
trip (or forever, on a wedged peer) — the migrate path runs on worker
threads, the scheduler only services its mailbox.  ``*Allocator`` classes (the paged-KV
block economy, serving/paged.py) sit ON the dispatch path — every
admission and block-table assembly runs them between dispatches — so
ALL their methods are roots: block-table math must stay host-side
numpy, and a ``.item()`` on the free list can never ride along
undeclared.  The engine DOES need exactly one fetch
boundary (delivering sampled tokens) and host-side numpy scheduler math
is legitimate — those sites carry ``# analysis: ok host-sync-in-dispatch``
pragmas, which is the point: the boundary is *declared*, so a new
undeclared one fails tier-1.

``jit-in-loop``: constructing a jit (or a ``make_*_program`` /
``mesh_jit``) inside a loop body builds a fresh Python callable per
iteration — each jax.jit object carries its own trace cache, so this is
a guaranteed recompile treadmill.  Program construction belongs in cached
getters (the ``_build_programs`` pattern); only *calling* a cached
program in a loop is fine.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from .astlint import Finding, LintContext, ParsedFile, rule

#: scheduler entry points: methods of any ``*Engine`` class from which
#: the dispatch-path reachability walk starts
ROOT_METHODS = ("_loop", "_loop_inner", "_admit", "_process", "step",
                "_dispatch")

_MAKE_PROGRAM = re.compile(r"^make_\w*_program$")

#: KV-tier classes (ISSUE 12): any class named *Tier*/*Spill*/
#: *Hibernat* joins the dispatch-hygiene walk (KvSpillStore,
#: SessionHibernator-style orchestrators) — substring, not suffix,
#: because the tier vocabulary composes into names freely
_TIER_CLASS = re.compile(r"Tier|Spill|Hibernat")


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _FileGraph:
    """Intra-file call graph: function qualname -> callee qualnames.

    Resolution is deliberately simple (and documented as such):
    ``self.X(...)`` resolves to method ``X`` of the enclosing class (and
    to an aliased nested function when the file assigns ``self.X = Y``,
    the ``_build_programs`` getter pattern); bare ``name(...)`` resolves
    to a module-level function of that name.  Cross-file calls are out
    of scope — the dispatch loop and its helpers live in one module by
    design.
    """

    def __init__(self, pf: ParsedFile):
        self.pf = pf
        self.funcs: dict[str, ast.AST] = {}      # qualname -> def node
        self.by_class: dict[str, dict[str, str]] = {}  # class -> name -> qual
        self.module_funcs: dict[str, str] = {}   # bare name -> qualname
        self.aliases: dict[tuple[str, str], str] = {}  # (class, attr) -> qual
        self.classes: dict[str, ast.ClassDef] = {}
        self._index(pf.tree, [])
        self._index_aliases()

    def _index(self, node: ast.AST, stack: list[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self.classes[child.name] = child
                self._index(child, stack + [child.name])
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = ".".join(stack + [child.name])
                self.funcs[qual] = child
                if not stack:
                    self.module_funcs[child.name] = qual
                else:
                    # owning class = first ClassDef on the stack path
                    cls = stack[0]
                    self.by_class.setdefault(cls, {})[child.name] = qual
                self._index(child, stack + [child.name])
            else:
                self._index(child, stack)

    def _index_aliases(self) -> None:
        # self.X = Y where Y names a function defined in this file: calls
        # through self.X reach Y (the cached-getter installation pattern)
        for qual, fn in list(self.funcs.items()):
            cls = qual.split(".")[0] if "." in qual else None
            if cls is None:
                continue
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                t = node.targets[0]
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        and isinstance(node.value, ast.Name)):
                    target = node.value.id
                    # innermost visible def: prefer one nested under qual
                    cand = f"{qual}.{target}"
                    if cand not in self.funcs:
                        cand = self.module_funcs.get(target, "")
                    if cand:
                        self.aliases[(cls, t.attr)] = cand

    def callees(self, qual: str) -> set[str]:
        fn = self.funcs.get(qual)
        if fn is None:
            return set()
        cls = qual.split(".")[0] if "." in qual else None
        out: set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name):
                cand = f"{qual}.{f.id}"
                if cand in self.funcs:
                    out.add(cand)
                elif f.id in self.module_funcs:
                    out.add(self.module_funcs[f.id])
            elif (isinstance(f, ast.Attribute)
                  and isinstance(f.value, ast.Name)
                  and f.value.id == "self" and cls is not None):
                m = self.by_class.get(cls, {}).get(f.attr)
                if m:
                    out.add(m)
                a = self.aliases.get((cls, f.attr))
                if a:
                    out.add(a)
        return out

    def reachable(self, roots: Iterable[str]) -> set[str]:
        seen: set[str] = set()
        todo = [r for r in roots if r in self.funcs]
        while todo:
            q = todo.pop()
            if q in seen:
                continue
            seen.add(q)
            todo.extend(self.callees(q) - seen)
        return seen


#: host-materialization calls: each entry is (label, matcher(Call) -> bool)
def _is_item(call: ast.Call) -> bool:
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "item" and not call.args)


def _is_tolist(call: ast.Call) -> bool:
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "tolist" and not call.args)


def _is_device_get(call: ast.Call) -> bool:
    d = _dotted(call.func)
    return d in ("jax.device_get", "device_get")


def _is_block_until_ready(call: ast.Call) -> bool:
    if isinstance(call.func, ast.Attribute) and (
            call.func.attr == "block_until_ready"):
        return True
    return _dotted(call.func) == "jax.block_until_ready"


def _is_np_materialize(call: ast.Call) -> bool:
    d = _dotted(call.func)
    if d not in ("np.asarray", "np.array", "numpy.asarray", "numpy.array",
                 "onp.asarray", "onp.array"):
        return False
    if not call.args:
        return False
    # materializing an obvious host literal is not a device fetch
    return not isinstance(call.args[0],
                          (ast.List, ast.ListComp, ast.Tuple, ast.Constant))


#: blocking socket I/O attribute calls: a ``sendall``/``recv`` reachable
#: from the scheduler stalls EVERY live request for a network round trip
#: (or forever, on a wedged peer) — the KV-migration streaming path
#: (ISSUE 8) must run on a worker thread, with the scheduler touching
#: only its mailbox.  ``send`` is deliberately absent: generator.send
#: and queue-ish .send() false-positive; migration code uses sendall.
_BLOCKING_SOCKET_ATTRS = {"sendall", "recv", "recv_into", "accept"}


def _is_blocking_socket(call: ast.Call) -> bool:
    if (isinstance(call.func, ast.Attribute)
            and call.func.attr in _BLOCKING_SOCKET_ATTRS):
        return True
    return _dotted(call.func) in ("socket.create_connection",
                                  "create_connection")


_REDUCERS = {"max", "min", "sum", "mean", "any", "all", "argmax", "argmin"}


def _is_scalarized_reduction(call: ast.Call) -> bool:
    """float(x.max()) / int(a[m].sum()): forces the reduced value to a
    Python scalar — a sync when x is a device array."""
    if not (isinstance(call.func, ast.Name)
            and call.func.id in ("float", "int", "bool")
            and len(call.args) == 1):
        return False
    a = call.args[0]
    return (isinstance(a, ast.Call) and isinstance(a.func, ast.Attribute)
            and a.func.attr in _REDUCERS)


_HOST_SYNCS = (
    ("`.item()`", _is_item),
    ("`.tolist()`", _is_tolist),
    ("`jax.device_get`", _is_device_get),
    ("`block_until_ready`", _is_block_until_ready),
    ("numpy materialization (`np.asarray`/`np.array`)", _is_np_materialize),
    ("scalarized reduction (`int`/`float` of `.max()`-like)",
     _is_scalarized_reduction),
    ("blocking socket I/O (`sendall`/`recv`/`create_connection` — "
     "migration streaming must run off-thread)", _is_blocking_socket),
)


@rule("host-sync-in-dispatch")
def host_sync_in_dispatch(ctx: LintContext) -> Iterable[Finding]:
    for pf in ctx.files.values():
        graph = _FileGraph(pf)
        roots = [
            f"{cls}.{m}"
            for cls in graph.classes if cls.endswith("Engine")
            for m in ROOT_METHODS
        ]
        # paged-KV allocators run between dispatches on the scheduler
        # thread: EVERY method is dispatch-path (block-table assembly,
        # free-list pops, prefix matching) — host numpy only.  Traffic-
        # plane admission classes (ISSUE 9: ``*TrafficPlane`` /
        # ``*Admission`` / ``*Preemptor``) get the same walk for the
        # inverse reason:
        # token-bucket and queue accounting runs on router/HTTP worker
        # threads and the engine's admission_policy hook runs ON the
        # scheduler thread — either way a device fetch or a blocking
        # socket in QoS bookkeeping stalls every live request, so it
        # must stay host-side stdlib.  Elastic-resize ORCHESTRATION
        # classes (ISSUE 10: ``*Resizer`` / ``*Reshard``) are rooted
        # too — the PR 8 ``*Preemptor`` lesson: new scheduler-adjacent
        # classes must not go unlinted.  A resizer's weight fetch is
        # DELIBERATE off-scheduler blocking, so each such site carries
        # a declaring pragma instead of silence.  The reshard WIRE
        # classes (ReshardServer/ReshardClient) follow the
        # KvMigrationServer convention instead: dedicated worker
        # threads whose whole job is socket I/O, never reachable from
        # an engine dispatch loop — suffix matching leaves them out on
        # purpose, exactly like the kv_migrate server.  The KV TIER
        # classes (ISSUE 12: ``*BlockPool`` suffix plus anything named
        # *Tier*/*Spill*/*Hibernat*) are rooted the same way:
        # HostBlockPool's match/take run ON the scheduler thread at
        # admission (host dict walks only), and the spill/hibernate
        # store's device fetches + file I/O are deliberate
        # off-scheduler tier transitions — every such site carries a
        # declaring pragma, so an UNdeclared fetch creeping into tier
        # bookkeeping fails tier-1 (spill I/O never on the scheduler;
        # the mailbox seam is the only crossing).  Autoscaling
        # ORCHESTRATION classes (ISSUE 15: ``*Autoscaler`` /
        # ``*Scaler`` / ``*Reaper``) are rooted for the same reason as
        # resizers: the decision loop's sensor reads run every tick on
        # the reconcile worker (or its own thread) against live-engine
        # state — a device fetch or blocking socket inside a sensor or
        # actuator closure turns every tick into a stall, so sensing
        # must stay host-side stdlib and heavy actuation must go
        # through the engines' public cross-thread APIs.  AOT program
        # ARTIFACT classes (ISSUE 17: ``*ArtifactCache`` /
        # ``*ProgramStore``) are rooted because artifact load/publish
        # is warmup-only by design: the seal boundary (RecompileCounter
        # arming) keeps disk I/O off the scheduler thread structurally,
        # and this root makes the complementary promise checkable — a
        # device fetch or blocking sync creeping into cache
        # bookkeeping (key hashing, manifest verify, counter reads)
        # would put host work back on the dispatch path every time a
        # program is consulted.
        roots += [
            qual
            for cls, methods in graph.by_class.items()
            if cls.endswith(("Allocator", "TrafficPlane", "Admission",
                             "Preemptor", "Resizer", "Reshard",
                             "BlockPool", "Autoscaler", "Scaler",
                             "Reaper", "ArtifactCache", "ProgramStore"))
            or _TIER_CLASS.search(cls)
            for qual in methods.values()
        ]
        if not roots:
            continue
        for qual in sorted(graph.reachable(roots)):
            fn = graph.funcs[qual]
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                for label, match in _HOST_SYNCS:
                    if match(node):
                        f = ctx.finding(
                            pf, "host-sync-in-dispatch", node,
                            f"host sync {label} reachable from the "
                            "engine dispatch loop")
                        if f:
                            yield f
                        break


def _is_program_construction(call: ast.Call) -> bool:
    f = call.func
    d = _dotted(f)
    if d in ("jax.jit", "jax.pmap"):
        return True
    name = None
    if isinstance(f, ast.Name):
        name = f.id
    elif isinstance(f, ast.Attribute):
        name = f.attr
    if name is None:
        return False
    return name == "mesh_jit" or bool(_MAKE_PROGRAM.match(name))


def walk_skip_defs(node: ast.AST) -> Iterable[ast.AST]:
    """ast.walk that does NOT descend into nested function/lambda bodies
    — a def inside the scanned region runs later (if ever), not here."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield child
        yield from walk_skip_defs(child)


@rule("jit-in-loop")
def jit_in_loop(ctx: LintContext) -> Iterable[Finding]:
    for pf in ctx.files.values():
        for loop in ast.walk(pf.tree):
            if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
                continue
            # scan only this loop's own body (nested defs build programs
            # lazily when *called* — construction is not per-iteration)
            for node in walk_skip_defs(loop):
                if isinstance(node, ast.Call) and _is_program_construction(
                        node):
                    f = ctx.finding(
                        pf, "jit-in-loop", node,
                        "jit/program construction inside a loop body "
                        "(recompile treadmill — hoist into a cached "
                        "getter)")
                    if f:
                        yield f
