"""``python -m kubeflow_tpu.analysis`` — the platform lint CLI.

Modes:
  (default)            lint, compare to the baseline, exit 1 on NEW
                       findings (the ratchet CI/tier-1 runs)
  --update-baseline    freeze the current findings as the new debt
  --json               machine-readable findings + summary on stdout
  --baseline PATH      compare/write a non-default baseline file
  --rule NAME          run a subset: a rule name OR a group alias
                       (``threads`` -> thread-affinity, ``protocol`` ->
                       op-table + fault-pairing, ``locks`` -> lock-order
                       + lock-blocking-call, ``persist`` -> torn-write,
                       ``dispatch``, ``hygiene``); repeatable
  --changed            parse the WHOLE platform (the call graph needs
                       every module) but report only findings in files
                       changed vs HEAD (+ untracked) — the fast
                       pre-commit loop
  --all                list every finding, not just the new ones
  --self-test          run the built-in rule fixtures (selftest.py) —
                       the lint binary validating itself, no pytest

Exit codes (CI contract, also asserted by tests/test_analysis.py):
  0  no findings above the baseline / self-test green / baseline written
  1  NEW findings above the ratchet baseline, or a self-test fixture
     failed (a rule stopped firing on its true positive or started
     firing on its near miss)
  2  usage error (argparse; e.g. --update-baseline with a subset lint)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

from .astlint import (
    LintReport,
    baseline_path,
    compare_to_baseline,
    load_baseline,
    rule_names,
    run_lint,
    write_baseline,
)

#: CLI group aliases -> registered rule names (the ``--rule threads`` /
#: ``--rule protocol`` filters): one word selects a concern, not a file
RULE_GROUPS: dict[str, tuple[str, ...]] = {
    "dispatch": ("host-sync-in-dispatch", "jit-in-loop"),
    "hygiene": ("swallowed-exception", "unsafe-pickle",
                "nondaemon-thread"),
    "locks": ("lock-order", "lock-blocking-call"),
    "threads": ("thread-affinity",),
    "protocol": ("op-table", "fault-pairing"),
    "metrics": ("metrics-contract",),
    "persist": ("torn-write",),
}


def resolve_rules(names) -> list[str] | None:
    """Expand group aliases into registered rule names (dedup, stable
    order)."""
    if names is None:
        return None
    out: list[str] = []
    for n in names:
        for r in RULE_GROUPS.get(n, (n,)):
            if r not in out:
                out.append(r)
    return out


def repo_root() -> str:
    """The checkout root = two levels above this package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def changed_paths(root: str) -> set[str]:
    """Repo-relative paths changed vs HEAD plus untracked files, from
    git.  Returns empty (-> report nothing) when git is unavailable:
    the --changed mode is a convenience filter, never a gate."""
    out: set[str] = set()
    for cmd in (("git", "diff", "--name-only", "HEAD"),
                ("git", "ls-files", "--others", "--exclude-standard")):
        try:
            res = subprocess.run(cmd, cwd=root, capture_output=True,
                                 text=True, check=True)
        except (OSError, subprocess.CalledProcessError):
            continue
        out.update(ln.strip() for ln in res.stdout.splitlines()
                   if ln.strip())
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubeflow_tpu.analysis",
        description="Platform analyzer: AST lint with a findings ratchet")
    ap.add_argument("paths", nargs="*",
                    help="files to lint (default: the platform dirs)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON path (default: "
                         "kubeflow_tpu/analysis/baseline.json)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="freeze current findings as the new baseline")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit machine-readable JSON")
    ap.add_argument("--rule", action="append", default=None,
                    choices=rule_names() + sorted(RULE_GROUPS),
                    help="run only this rule or group alias "
                         "(threads, protocol, locks, dispatch, hygiene; "
                         "repeatable)")
    ap.add_argument("--changed", action="store_true",
                    help="report only findings in files changed vs HEAD "
                         "(+ untracked); the full platform is still "
                         "parsed so cross-module effects stay visible")
    ap.add_argument("--all", action="store_true",
                    help="print every finding, not only new ones")
    ap.add_argument("--self-test", action="store_true", dest="self_test",
                    help="run the built-in rule fixtures instead of "
                         "linting the repo (0 = all green)")
    args = ap.parse_args(argv)

    rules = resolve_rules(args.rule)
    if args.self_test:
        if (args.paths or args.baseline or args.update_baseline
                or args.as_json or args.all or args.changed):
            # the fixtures lint synthetic sources, not the repo: a
            # --json/--baseline caller would get fixture chatter + exit
            # 0 where it expects the documented lint contract
            ap.error("--self-test runs the built-in fixtures only; it "
                     "is incompatible with paths, --baseline, "
                     "--update-baseline, --json, --all, and --changed "
                     "(--rule filters which fixtures run)")
        from .selftest import run_selftest
        return run_selftest(rules=rules)

    root = os.path.abspath(args.root) if args.root else repo_root()
    bpath = args.baseline or baseline_path(root)
    paths = [os.path.abspath(p) for p in args.paths] or None
    if args.update_baseline and (paths or args.rule or args.changed):
        # a subset lint would OVERWRITE the baseline with only the
        # subset's findings, silently erasing every other frozen entry —
        # the next full run then fails tier-1 on debt nobody added
        ap.error("--update-baseline requires a full lint "
                 "(no positional paths, no --rule, no --changed)")
    if args.changed and paths:
        ap.error("--changed derives its scope from git; positional "
                 "paths would fight it — pass one or the other")
    t0 = time.perf_counter()
    report = run_lint(root, paths=paths, rules=rules)
    elapsed_s = round(time.perf_counter() - t0, 3)
    scope_note = ""
    if args.changed:
        changed = changed_paths(root)
        report = LintReport([f for f in report.findings
                             if f.path in changed])
        scope_note = f" [--changed: {len(changed)} files in scope]"

    if args.update_baseline:
        doc = write_baseline(bpath, report)
        if args.as_json:
            print(json.dumps(doc, indent=1))
        else:
            print(f"baseline updated: {bpath} "
                  f"({len(report.findings)} findings frozen: "
                  f"{doc['by_rule']})")
        return 0

    baseline = load_baseline(bpath)
    new = compare_to_baseline(report, baseline)

    if args.as_json:
        print(json.dumps({
            "total": len(report.findings),
            "by_rule": report.by_rule(),
            "baseline_total": sum(baseline.values()),
            "new": [vars(f) for f in new],
            "elapsed_s": elapsed_s,
            "changed_only": bool(args.changed),
        }, indent=1))
    else:
        shown = report.findings if args.all else new
        for f in shown:
            print(f)
        print(f"platform_lint: {len(report.findings)} findings "
              f"({report.by_rule() or 'clean'}), "
              f"{sum(baseline.values())} baselined, {len(new)} NEW "
              f"in {elapsed_s}s{scope_note}")
        if new:
            print("new findings above the ratchet baseline — fix them, "
                  "pragma them with a reason, or (for reviewed debt) "
                  "re-freeze with --update-baseline", file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
