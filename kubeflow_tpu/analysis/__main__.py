"""``python -m kubeflow_tpu.analysis`` — the platform lint CLI.

Modes:
  (default)            lint, compare to the baseline, exit 1 on NEW
                       findings (the ratchet CI/tier-1 runs)
  --update-baseline    freeze the current findings as the new debt
  --json               machine-readable findings + summary on stdout
  --baseline PATH      compare/write a non-default baseline file
  --rule NAME          run a subset of rules (repeatable)
  --all                list every finding, not just the new ones

Exit codes: 0 = no findings above baseline; 1 = new findings; 2 = usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .astlint import (
    baseline_path,
    compare_to_baseline,
    load_baseline,
    rule_names,
    run_lint,
    write_baseline,
)


def repo_root() -> str:
    """The checkout root = two levels above this package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubeflow_tpu.analysis",
        description="Platform analyzer: AST lint with a findings ratchet")
    ap.add_argument("paths", nargs="*",
                    help="files to lint (default: the platform dirs)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON path (default: "
                         "kubeflow_tpu/analysis/baseline.json)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="freeze current findings as the new baseline")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit machine-readable JSON")
    ap.add_argument("--rule", action="append", default=None,
                    choices=rule_names(),
                    help="run only this rule (repeatable)")
    ap.add_argument("--all", action="store_true",
                    help="print every finding, not only new ones")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root) if args.root else repo_root()
    bpath = args.baseline or baseline_path(root)
    paths = [os.path.abspath(p) for p in args.paths] or None
    if args.update_baseline and (paths or args.rule):
        # a subset lint would OVERWRITE the baseline with only the
        # subset's findings, silently erasing every other frozen entry —
        # the next full run then fails tier-1 on debt nobody added
        ap.error("--update-baseline requires a full lint "
                 "(no positional paths, no --rule)")
    report = run_lint(root, paths=paths, rules=args.rule)

    if args.update_baseline:
        doc = write_baseline(bpath, report)
        if args.as_json:
            print(json.dumps(doc, indent=1))
        else:
            print(f"baseline updated: {bpath} "
                  f"({len(report.findings)} findings frozen: "
                  f"{doc['by_rule']})")
        return 0

    baseline = load_baseline(bpath)
    new = compare_to_baseline(report, baseline)

    if args.as_json:
        print(json.dumps({
            "total": len(report.findings),
            "by_rule": report.by_rule(),
            "baseline_total": sum(baseline.values()),
            "new": [vars(f) for f in new],
        }, indent=1))
    else:
        shown = report.findings if args.all else new
        for f in shown:
            print(f)
        print(f"platform_lint: {len(report.findings)} findings "
              f"({report.by_rule() or 'clean'}), "
              f"{sum(baseline.values())} baselined, {len(new)} NEW")
        if new:
            print("new findings above the ratchet baseline — fix them, "
                  "pragma them with a reason, or (for reviewed debt) "
                  "re-freeze with --update-baseline", file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
