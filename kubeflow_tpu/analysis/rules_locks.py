"""``lock-order``: the platform-wide lock nesting graph.

PR 1's chaos harness caught a double restart-bump from two reconciles
racing one key — a bug class locks *create* as readily as they fix.
This rule extracts every ``with <lock>:`` nesting across the serving,
controlplane, hpo, and net layers, builds the global acquisition-order
graph, and flags:

- **cycles** (``A`` taken under ``B`` somewhere, ``B`` under ``A``
  elsewhere): a deadlock that needs only the right two-thread schedule —
  exactly what fault injection eventually finds, so find it at lint
  time instead;
- **blocking calls while holding a lock** (``time.sleep``, socket
  send/recv/connect/accept, thread ``join``, ``urlopen``, jax fetches):
  every other thread needing that lock now waits on the network/device
  too — the convoy that turns one slow peer into a platform stall.
  The gang channel's bounded ``sendall``-under-lock sites are the
  intentional, documented exception (socket timeouts bound the hold)
  and carry pragmas.

Lock identity is lexical: ``self._lock`` in class ``Foo`` is
``Foo._lock``; a module-level ``_lock`` is ``module._lock``.  Two
*instances* of one class share an identity here — over-approximate for
cycles (a self-edge via two instances is real ONLY if two objects nest;
those are skipped), under-approximate across files.  One level of
interprocedural depth is modeled: a call made under a lock pulls in the
locks that callee (same file) lexically takes.

Runtime truth — orders that only happen under fault injection — is the
:class:`~kubeflow_tpu.analysis.runtime.LockAudit` recorder's job; this
rule is the static floor.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, Optional

from .astlint import Finding, LintContext, ParsedFile, rule
from .callgraph import BLOCKING_EFFECTS, LIFECYCLE_METHODS, get_graph
from .rules_dispatch import _dotted, walk_skip_defs

#: layers whose locking interacts (the cross-component deadlock surface)
LOCK_SCOPE_PREFIXES = (
    "kubeflow_tpu/serving/",
    "kubeflow_tpu/controlplane/",
    "kubeflow_tpu/hpo/",
    "kubeflow_tpu/utils/net.py",
    "kubeflow_tpu/chaos/",
    "kubeflow_tpu/native/",
)

#: lexical lock-name markers.  "cv" entered with PR 9: serving/resize.py
#: guards reshard acks with a ``threading.Condition`` named ``_ack_cv``
#: and serving/traffic.py parks class waiters on per-class ``cond``s —
#: Conditions ARE locks (they wrap one), so leaving them out of the
#: nesting graph silently exempted the two newest modules from the
#: deadlock check.
_LOCKISH = ("lock", "gate", "cond", "mutex", "joined", "cv")


def _lock_name(expr: ast.AST, pf: ParsedFile, cls: str) -> Optional[str]:
    """Canonical lock id for a with-item context expr, or None if the
    expr doesn't look like a lock."""
    d = _dotted(expr)
    if d is None:
        return None
    last = d.rsplit(".", 1)[-1].lower()
    if not any(k in last for k in _LOCKISH):
        return None
    mod = os.path.splitext(os.path.basename(pf.relpath))[0]
    if d == "self" or d.startswith("self."):
        owner = cls or mod
        return f"{owner}.{d[5:]}" if d != "self" else None
    if "." not in d:
        return f"{mod}.{d}"
    return d  # obj._lock style: keep the dotted text as identity


class _WithLock:
    def __init__(self, name: str, node: ast.With, pf: ParsedFile):
        self.name = name
        self.node = node
        self.pf = pf


def _enclosing_class(pf: ParsedFile, line: int) -> str:
    scope = pf.scope_at(line)
    return scope.split(".")[0] if scope else ""


def _iter_with_locks(pf: ParsedFile):
    """Every (lock-name, With-node) in the file, lexical."""
    for node in pf.of_type(ast.With, ast.AsyncWith):
        cls = _enclosing_class(pf, node.lineno)
        for item in node.items:
            name = _lock_name(item.context_expr, pf, cls)
            if name:
                yield name, node


def _locks_in_body(pf: ParsedFile, node: ast.AST) -> list[tuple[str, ast.With]]:
    """with-lock statements lexically inside ``node``'s body (not
    descending into nested defs — they run on other threads/later)."""
    out = []
    for child in walk_skip_defs(node, pf.children):
        if not isinstance(child, (ast.With, ast.AsyncWith)):
            continue
        cls = _enclosing_class(pf, child.lineno)
        for item in child.items:
            name = _lock_name(item.context_expr, pf, cls)
            if name:
                out.append((name, child))
    return out


def _function_index(pf: ParsedFile) -> dict[str, ast.AST]:
    """(class, name) and bare-name keyed defs for 1-level call lookup,
    read off the parse-time def table (no re-recursion)."""
    idx: dict[str, ast.AST] = {}
    for node, _qual, _inner, outer, _top in pf.defs:
        idx[f"{outer}.{node.name}" if outer else node.name] = node
    return idx


_BLOCKING_SOCKET = {"recv", "send", "sendall", "accept", "connect",
                    "create_connection", "recv_into"}


def _blocking_label(call: ast.Call) -> Optional[str]:
    d = _dotted(call.func)
    if d in ("time.sleep", "sleep"):
        return "`time.sleep`"
    if d in ("jax.device_get", "jax.block_until_ready"):
        return f"`{d}`"
    f = call.func
    if isinstance(f, ast.Attribute):
        if f.attr in _BLOCKING_SOCKET:
            return f"socket `.{f.attr}`"
        if f.attr == "block_until_ready":
            return "`.block_until_ready`"
        if f.attr == "urlopen" or (isinstance(f.value, ast.Name)
                                   and f.attr == "urlopen"):
            return "`urlopen`"
        if f.attr == "join" and "thread" in (_dotted(f.value) or "").lower():
            return "thread `.join`"
    if isinstance(f, ast.Name) and f.id == "urlopen":
        return "`urlopen`"
    return None


def collect_lock_graph(ctx: LintContext) -> tuple[
        dict[tuple[str, str], tuple[ParsedFile, ast.AST]],
        list[tuple[ParsedFile, ast.AST, str, str]]]:
    """The platform-wide lock graph: ``(edges, blocking_sites)``.

    ``edges`` maps (outer, inner) nesting pairs to the first site that
    creates them; ``blocking_sites`` lists (pf, node, label, lock)
    blocking calls made while a lock is held.  Exposed so tests can
    re-verify acyclicity and coverage (the PR 8/9 satellite: resize.py's
    ``_ack_cv`` Condition and traffic.py's per-class ``cond``s must
    actually appear in this graph)."""
    edges: dict[tuple[str, str], tuple[ParsedFile, ast.AST]] = {}
    blocking: list[tuple[ParsedFile, ast.AST, str, str]] = []

    scoped = [pf for rel, pf in sorted(ctx.files.items())
              if rel.startswith(LOCK_SCOPE_PREFIXES)]

    # per-file: lexical nesting edges + blocking-under-lock + 1-level
    # call expansion
    for pf in scoped:
        fidx = _function_index(pf)
        for outer_name, outer_node in _iter_with_locks(pf):
            body = list(walk_skip_defs(outer_node, pf.children))
            # direct lexical nesting
            for inner_name, inner_node in _locks_in_body(pf, outer_node):
                if inner_name != outer_name:
                    edges.setdefault((outer_name, inner_name),
                                     (pf, inner_node))
            for child in body:
                if not isinstance(child, ast.Call):
                    continue
                # blocking call while the lock is held
                label = _blocking_label(child)
                if label is not None:
                    blocking.append((pf, child, label, outer_name))
                    continue
                # 1-level interprocedural: locks the callee takes are
                # taken under this one
                callee = None
                fn = child.func
                if isinstance(fn, ast.Name):
                    callee = fidx.get(fn.id)
                elif (isinstance(fn, ast.Attribute)
                      and isinstance(fn.value, ast.Name)
                      and fn.value.id == "self"):
                    cls = _enclosing_class(pf, child.lineno)
                    callee = fidx.get(f"{cls}.{fn.attr}")
                if callee is not None:
                    for inner_name, inner_node in _locks_in_body(pf, callee):
                        if inner_name != outer_name:
                            edges.setdefault((outer_name, inner_name),
                                             (pf, child))
    return edges, blocking


def find_cycles(edges: dict[tuple[str, str], tuple[ParsedFile, ast.AST]]
                ) -> list[tuple[list[str], ParsedFile, ast.AST]]:
    """Distinct lock-order cycles in ``edges``: (witness path, anchor
    site) per cycle node-set, anchored at the smallest source node for
    ratchet-stable identity."""
    # cycle detection: edge a->b closes a cycle iff a is reachable back
    # from b.  BFS with parent links reconstructs one witness path;
    # each distinct node set reports once, anchored at the edge whose
    # source node is smallest (stable across runs for the ratchet key).
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    out: list[tuple[list[str], ParsedFile, ast.AST]] = []
    reported: set[frozenset] = set()
    for a, b in sorted(edges):
        parent: dict[str, str] = {b: b}
        queue = [b]
        while queue:
            node = queue.pop(0)
            if node == a:
                break
            for nxt in sorted(graph.get(node, ())):
                if nxt not in parent:
                    parent[nxt] = node
                    queue.append(nxt)
        if a not in parent:
            continue
        path = [a]  # parent-chain hop-back: a, parent[a], ..., b
        node = a
        while node != b:
            node = parent[node]
            path.append(node)
        # forward cycle = a --edge--> b --bfs-walk--> ... --> a
        cycle = [a] + list(reversed(path))[:-1]
        nodes = frozenset(cycle)
        if nodes in reported or min(cycle) != a:
            continue
        reported.add(nodes)
        pf, where = edges[(a, b)]
        out.append((cycle, pf, where))
    return out


@rule("lock-order")
def lock_order(ctx: LintContext) -> Iterable[Finding]:
    edges, blocking = collect_lock_graph(ctx)
    for pf, node, label, outer_name in blocking:
        f = ctx.finding(
            pf, "lock-order", node,
            f"blocking call {label} while holding `{outer_name}`")
        if f:
            yield f
    for cycle, pf, where in find_cycles(edges):
        f = ctx.finding(
            pf, "lock-order", where,
            "lock-order cycle: " + " -> ".join(cycle + [cycle[0]]))
        if f:
            yield f


#: effect -> human label for the lock-blocking-call message
_EFFECT_LABELS = {
    "sleep": "`time.sleep`",
    "socket": "blocking socket I/O",
    "host-sync": "a device sync/fetch",
    "fsync": "`os.fsync`",
    "urlopen": "`urlopen`",
    "thread-join": "thread `.join`",
}


@rule("lock-blocking-call")
def lock_blocking_call(ctx: LintContext) -> Iterable[Finding]:
    """No blocking I/O or device sync REACHABLE while a Lock/RLock/
    Condition is held — the transitive completion of lock-order's
    direct-site check.  ``with self._lock: self._flush()`` is invisible
    to lock-order when ``_flush`` fsyncs (or its callee three modules
    away does); this rule joins the same lexical lock model to the
    call-graph effect sets, so the convoy — every thread needing the
    lock waiting on disk/network/device — is flagged wherever the
    blocking call actually lives.  Direct blocking calls under the
    ``with`` stay lock-order's finding (one site, one rule); this one
    fires only through a resolved call edge, and names the terminal
    site so the fix (or the declaring pragma) lands at the right
    boundary."""
    graph = get_graph(ctx)
    scoped = [pf for rel, pf in sorted(ctx.files.items())
              if rel.startswith(LOCK_SCOPE_PREFIXES)]
    for pf in scoped:
        for lock_name, with_node in _iter_with_locks(pf):
            scope = pf.scope_at(with_node.lineno)
            if scope.rsplit(".", 1)[-1] in LIFECYCLE_METHODS:
                # warmup/__init__/close hold their gate to SERIALIZE a
                # phase transition — blocking while every other thread
                # waits is the intended semantics there, and the phase
                # contract (rules_threads._LIFECYCLE) already owns it
                continue
            for child in walk_skip_defs(with_node, pf.children):
                if not isinstance(child, ast.Call):
                    continue
                if _blocking_label(child) is not None:
                    continue  # direct site: lock-order reports it
                hit = None
                for callee in graph.resolve_call(child):
                    eff = sorted(graph.effects(callee) & BLOCKING_EFFECTS)
                    if eff:
                        hit = (callee, eff[0])
                        break
                if hit is None:
                    continue
                callee, eff = hit
                site, _label = graph.effect_site(callee, eff) or (callee, "")
                f = ctx.finding(
                    pf, "lock-blocking-call", child,
                    f"call into `{callee}` while holding `{lock_name}` "
                    f"reaches {_EFFECT_LABELS[eff]} (at `{site}`) — "
                    "move the blocking work outside the lock or declare "
                    "the boundary")
                if f:
                    yield f
