"""Causal GQA flash attention: Pallas TPU kernels (fwd + bwd).

The hot op of the Llama family, owned by the framework (SURVEY.md §7
"Pallas kernel" hard part).  Standard FlashAttention-2 scheme laid out for
the TPU memory hierarchy:

- grid iterates (batch*head, q_block, k_block) with the K dimension
  innermost; online-softmax state (m, l, acc) lives in VMEM scratch and
  persists across the sequential TPU grid — no [s, s] matrix ever exists
  in HBM;
- blocks are MXU-shaped ([block, 128] lanes, f32 accumulation via
  ``preferred_element_type``), bf16 inputs stream straight from HBM;
- causal structure is exploited at block granularity (fully-masked blocks
  are skipped with ``pl.when``, the diagonal block gets the triangular
  mask);
- backward recomputes P from the saved logsumexp (no attention matrix
  residual) in two passes: one accumulating dK/dV per KV block, one
  accumulating dQ per Q block — wrapped as ``jax.custom_vjp``.

GQA is handled by index-mapping each query head onto its shared KV head —
KV blocks are never materialized per-query-head.

On non-TPU backends the kernels run in Pallas interpret mode, so the same
code path is testable on the CPU mesh (SURVEY.md §4).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block(seq_len: int, want: int) -> int:
    b = min(want, seq_len)
    while seq_len % b:
        b //= 2
    return max(b, 1)


def _pad_to_tileable(s: int, want: int) -> int:
    """Length >= s whose block divisor is MXU-tileable (mult of 8, >= 128).

    Odd sequence lengths (e.g. next-token training slices seq to L-1) would
    otherwise collapse the block size to 1, which Pallas cannot lay out.
    Padding the sequence is sound for causal attention: padded keys sit at
    positions greater than every real query, so the causal mask hides them;
    padded query rows are sliced off on return.
    """
    b = _block(s, want)
    if b >= 128 and b % 8 == 0:
        return s
    unit = min(want, 128)
    return ((s + unit - 1) // unit) * unit


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale: float, block_q: int, block_k: int, causal: bool):
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _body():
        q = q_ref[0].astype(jnp.float32) * scale          # [bq, d]
        k = k_ref[0].astype(jnp.float32)                  # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, bk]
        if causal:
            # global causal mask; only bites on diagonal-straddling blocks
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)

        m_prev, l_prev = m_scr[:], l_scr[:]
        m_new = jnp.maximum(m_prev, s.max(axis=1))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])                   # [bq, bk]
        l_scr[:] = l_prev * corr + p.sum(axis=1)
        v = v_ref[0].astype(jnp.float32)                  # [bk, d]
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * corr[:, None] + pv
        m_scr[:] = m_new

    if causal:
        # causal block skip: compute only if some k pos <= some q pos
        pl.when(ki * block_k <= qi * block_q + block_q - 1)(_body)
    else:
        _body()

    @pl.when(ki == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l[:, None]).astype(o_ref.dtype)
        lse = m_scr[:] + jnp.log(l)
        # lse rides a [*, 8] layout: TPU block specs need the trailing
        # two dims tile-compatible, so scalars-per-row get 8 lanes
        lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])


def _fwd(q3, k3, v3, *, h: int, kv: int, scale: float,
         block_q: int, block_k: int, causal: bool = True):
    """q3: [b*h, s, d]; k3/v3: [b*kv, s, d] -> (o [b*h, s, d], lse [b*h, s])."""
    bh, s, d = q3.shape
    g = h // kv
    nq, nk = s // block_q, s // block_k

    def kv_index(bhi, qi, ki):
        return ((bhi // h) * kv + (bhi % h) // g, ki, 0)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal)
    return pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((1, block_q, 8), lambda bhi, qi, ki: (bhi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q3.shape, q3.dtype),
            jax.ShapeDtypeStruct((bh, s, 8), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q3, k3, v3)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, scale: float, block_q: int, block_k: int, causal: bool):
    ki, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _body():
        q = q_ref[0].astype(jnp.float32) * scale         # [bq, d]
        k = k_ref[0].astype(jnp.float32)                 # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse_ref[0, :, 0][:, None])             # [bq, bk]
        do = do_ref[0].astype(jnp.float32)               # [bq, d]
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bk, d]
        v = v_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bq, bk]
        ds = p * (dp - delta_ref[0, :, 0][:, None]) * scale  # [bq, bk]
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) / scale  # q was pre-scaled

    if causal:
        pl.when(qi * block_q + block_q - 1 >= ki * block_k)(_body)
    else:
        _body()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr,
                   *, scale: float, block_q: int, block_k: int, causal: bool):
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _body():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse_ref[0, :, 0][:, None])
        do = do_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, :, 0][:, None]) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(ki * block_k <= qi * block_q + block_q - 1)(_body)
    else:
        _body()

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd(h, kv, scale, block_q, block_k, residuals, do4,
         dlse2=None, causal=True):
    q3, k3, v3, o3, lse = residuals
    bh, s, d = q3.shape
    bkv = k3.shape[0]
    g = h // kv
    do3 = do4
    delta2 = jnp.sum(
        do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1)  # [bh, s]
    if dlse2 is not None:
        # lse cotangent folds into the same kernels: d(lse)/d(s) = p, so
        # ds = p*(dp - delta + dlse) — i.e. replace delta with delta - dlse
        delta2 = delta2 - dlse2.astype(jnp.float32)
    delta = jnp.broadcast_to(delta2[:, :, None], (*delta2.shape, 8))

    def kv_index_k_outer(bhi, ki, qi):
        return ((bhi // h) * kv + (bhi % h) // g, ki, 0)

    nq, nk = s // block_q, s // block_k
    # dK/dV: one pass per query head; shared KV heads summed afterwards
    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal)
    dk_per_h, dv_per_h = pl.pallas_call(
        dkv_kernel,
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bhi, ki, qi: (bhi, qi, 0)),
            pl.BlockSpec((1, block_k, d), kv_index_k_outer),
            pl.BlockSpec((1, block_k, d), kv_index_k_outer),
            pl.BlockSpec((1, block_q, d), lambda bhi, ki, qi: (bhi, qi, 0)),
            pl.BlockSpec((1, block_q, 8), lambda bhi, ki, qi: (bhi, qi, 0)),
            pl.BlockSpec((1, block_q, 8), lambda bhi, ki, qi: (bhi, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bhi, ki, qi: (bhi, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bhi, ki, qi: (bhi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q3, k3, v3, do3, lse, delta)
    # sum query-head contributions into the shared KV heads
    b = bh // h
    dk3 = dk_per_h.reshape(b, kv, g, s, d).sum(axis=2).reshape(bkv, s, d)
    dv3 = dv_per_h.reshape(b, kv, g, s, d).sum(axis=2).reshape(bkv, s, d)

    def kv_index_q_outer(bhi, qi, ki):
        return ((bhi // h) * kv + (bhi % h) // g, ki, 0)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal)
    dq3 = pl.pallas_call(
        dq_kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((1, block_k, d), kv_index_q_outer),
            pl.BlockSpec((1, block_k, d), kv_index_q_outer),
            pl.BlockSpec((1, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((1, block_q, 8), lambda bhi, qi, ki: (bhi, qi, 0)),
            pl.BlockSpec((1, block_q, 8), lambda bhi, qi, ki: (bhi, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q3.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(q3, k3, v3, do3, lse, delta)
    return dq3, dk3, dv3


# ---------------------------------------------------------------------------
# public api
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q3, k3, v3, heads, block):
    h, kv = heads
    scale = 1.0 / math.sqrt(q3.shape[-1])
    o, _ = _fwd(q3, k3, v3, h=h, kv=kv, scale=scale,
                block_q=block[0], block_k=block[1])
    return o


def _flash_fwd(q3, k3, v3, heads, block):
    h, kv = heads
    scale = 1.0 / math.sqrt(q3.shape[-1])
    o, lse = _fwd(q3, k3, v3, h=h, kv=kv, scale=scale,
                  block_q=block[0], block_k=block[1])
    return o, (q3, k3, v3, o, lse)


def _flash_bwd(heads, block, residuals, g):
    h, kv = heads
    scale = 1.0 / math.sqrt(residuals[0].shape[-1])
    return _bwd(h, kv, scale, block[0], block[1], residuals, g)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_lse(q3, k3, v3, heads, block, causal):
    h, kv = heads
    scale = 1.0 / math.sqrt(q3.shape[-1])
    o, lse = _fwd(q3, k3, v3, h=h, kv=kv, scale=scale,
                  block_q=block[0], block_k=block[1], causal=causal)
    return o, lse[:, :, 0]


def _flash_lse_fwd(q3, k3, v3, heads, block, causal):
    h, kv = heads
    scale = 1.0 / math.sqrt(q3.shape[-1])
    o, lse = _fwd(q3, k3, v3, h=h, kv=kv, scale=scale,
                  block_q=block[0], block_k=block[1], causal=causal)
    return (o, lse[:, :, 0]), (q3, k3, v3, o, lse)


def _flash_lse_bwd(heads, block, causal, residuals, cts):
    h, kv = heads
    do, dlse = cts
    scale = 1.0 / math.sqrt(residuals[0].shape[-1])
    return _bwd(h, kv, scale, block[0], block[1], residuals, do,
                dlse2=dlse, causal=causal)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_lse(
    q: jax.Array, k: jax.Array, v: jax.Array,
    *, q_per_kv: int = 1, block_q: int = 1024, block_k: int = 1024,
    causal: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """(out [b,s,h,d], lse [b,h,s]) — the block-combinable form.

    ``causal=False`` computes full (bidirectional) attention over the K/V
    block — what a ring-attention device needs for K/V blocks that sit
    entirely before its queries.  Partial results from multiple K/V blocks
    combine exactly via their logsumexps (parallel/ring_attention.py); the
    lse output is differentiable (its cotangent folds into the same bwd
    kernels through the delta rows).

    Requires MXU-tileable sequence lengths (no pad-and-slice here: padded
    keys would be ATTENDED under causal=False, so padding is unsound).
    """
    b, s, h, d = q.shape
    kv = k.shape[2]
    sk = k.shape[1]
    if h != kv * q_per_kv:
        raise ValueError(
            f"q_per_kv={q_per_kv} inconsistent with heads {h}, kv {kv}")
    if sk != s:
        raise ValueError(
            f"flash_attention_lse needs equal q/k lengths (got {s} vs {sk}); "
            "ring blocks are same-sized by construction")
    bq = _block(s, block_q)
    bk = _block(sk, block_k)
    if not _interpret() and (bq % 8 or bk % 8):
        raise ValueError(
            f"flash_attention_lse needs tileable seq lengths; got q={s}, "
            f"k={sk} (blocks {bq}x{bk})")
    q3 = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    k3 = k.transpose(0, 2, 1, 3).reshape(b * kv, sk, d)
    v3 = v.transpose(0, 2, 1, 3).reshape(b * kv, sk, d)
    o3, lse3 = _flash_lse(q3, k3, v3, (h, kv), (bq, bk), causal)
    return (o3.reshape(b, h, s, d).transpose(0, 2, 1, 3),
            lse3.reshape(b, h, s))


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    *, q_per_kv: int = 1, block_q: int = 1024, block_k: int = 1024,
) -> jax.Array:
    """Causal GQA flash attention; drop-in for the dense reference.

    q: [b, s, h, d]; k, v: [b, s, kv, d] with h = kv * q_per_kv.
    """
    b, s, h, d = q.shape
    kv = k.shape[2]
    sp = _pad_to_tileable(s, max(block_q, block_k))
    if sp != s:
        pad = [(0, 0), (0, sp - s), (0, 0), (0, 0)]
        q, k, v = jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad)
    bq = _block(sp, block_q)
    bk = _block(sp, block_k)
    q3 = q.transpose(0, 2, 1, 3).reshape(b * h, sp, d)
    k3 = k.transpose(0, 2, 1, 3).reshape(b * kv, sp, d)
    v3 = v.transpose(0, 2, 1, 3).reshape(b * kv, sp, d)
    o3 = _flash(q3, k3, v3, (h, kv), (bq, bk))
    out = o3.reshape(b, h, sp, d).transpose(0, 2, 1, 3)
    return out[:, :s] if sp != s else out
