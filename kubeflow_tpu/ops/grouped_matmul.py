"""Grouped matrix multiply: the dropless-MoE expert GEMM.

``out[i] = x[i] @ w[g(i)]`` where rows of ``x`` are SORTED by group and
``group_offsets`` [E+1] gives each group's contiguous range — the layout
the ragged MoE dispatch produces (models/moe.py).  The masked-scan
fallback there computes every expert's full-buffer matmul (E x the
useful FLOPs); a grouped GEMM touches each row tile once.

Implementation: delegates to Pallas' MegaBlox ``gmm`` kernel
(jax.experimental.pallas.ops.tpu.megablox), the production block-sparse
grouped matmul — it builds tile/group visit tables from the group sizes
so each LHS row tile is visited once per overlapping group and RHS
expert blocks stream once per (group, n-tile), and it carries a custom
VJP (dx via gmm against transposed RHS, dw via the transposed tgmm
kernel).  A first-principles Pallas kernel lived here briefly; measured
on v5e it re-streamed the expert weights once per row tile (~GBs per
matmul) and lost to the masked fallback — the tile-table structure is
the whole game, so the library kernel is the right engineering call.

This wrapper pins the repo's contract on top:

- offsets [E+1] API (what the dispatch math produces) -> group sizes;
- rows at or past ``offsets[-1]`` (padding / invalid transport rows)
  return ZEROS — megablox leaves tiles beyond the last group unwritten;
- shape-adaptive tiling so tiny CPU-test shapes work, and interpret mode
  off-TPU (flash-attention convention: the identical kernel is what the
  CPU suite exercises).
"""

from __future__ import annotations

import importlib
import sys

import jax
import jax.numpy as jnp

importlib.import_module("jax.experimental.pallas.ops.tpu.megablox")
_mb = sys.modules["jax.experimental.pallas.ops.tpu.megablox.gmm"]


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block(dim: int, want: int) -> int:
    b = min(want, dim)
    while dim % b:
        b //= 2
    return max(b, 1)


#: (tm, tk, tn) tile REQUEST for the megablox kernels (clamped per-shape
#: by _block); tune via set_gmm_tiling or $KFT_GMM_TILING="tm,tk,tn".
#: Default from the r4 v5e sweep (scripts/moe_bench.py --sweep, PERF.md):
#: (512,1024,1024) runs the E=8 top-2 bench layer at 13.3 ms/step vs the
#: old 128^3 tiles' 69.4 — the "grouped GEMM is 20% efficient" r3
#: finding was a tiling artifact, not a kernel property.  Larger tiles
#: ((1024,512,1408)) exceed v5e's 16M scoped VMEM and fail to compile;
#: tn must stay 128-aligned.
_TILING = (512, 1024, 1024)
#: accumulator dtype for the gmm products.  f32 is the safe default; the
#: bf16 lever halves accumulator traffic but loses mantissa on long
#: k-reductions — measured, not assumed (moe_bench --sweep).
_ACC_DTYPE = jnp.float32


def set_gmm_tiling(tm: int, tk: int, tn: int, acc_dtype=None) -> None:
    """Override the grouped-GEMM tile request (and optionally the
    accumulator dtype) — the tuning surface the MoE bench sweeps."""
    global _TILING, _ACC_DTYPE
    _TILING = (int(tm), int(tk), int(tn))
    if acc_dtype is not None:
        _ACC_DTYPE = acc_dtype


def _env_tiling() -> None:
    import os

    spec = os.environ.get("KFT_GMM_TILING")
    if spec:
        tm, tk, tn = (int(v) for v in spec.split(","))
        set_gmm_tiling(tm, tk, tn)


_env_tiling()


def _gmm(x, w, offsets):
    """Raw megablox call + the no-group-row contract: the kernel never
    visits tiles past the last group, so those output rows come back as
    uninitialized memory — pin them to zeros."""
    b, h = x.shape
    m = w.shape[-1]
    tm, tk, tn = _TILING
    sizes = jnp.diff(offsets).astype(jnp.int32)
    out = _mb.gmm(
        x, w, sizes,
        preferred_element_type=_ACC_DTYPE,
        tiling=(_block(b, tm), _block(h, tk), _block(m, tn)),
        interpret=_interpret(),
    )
    rows = jnp.arange(b, dtype=jnp.int32)
    return jnp.where(rows[:, None] < offsets[-1], out, 0.0).astype(x.dtype)


@jax.custom_vjp
def grouped_matmul(x: jax.Array, w: jax.Array, offsets: jax.Array) -> jax.Array:
    """``out[i] = x[i] @ w[e]`` for rows ``offsets[e] <= i < offsets[e+1]``.

    x: [B, h] rows sorted/grouped by expert; w: [E, h, m]; offsets:
    int32 [E+1] monotone group boundaries (rows >= offsets[-1] belong to
    no group and produce zeros).  Returns [B, m] in x.dtype.

    Own VJP (instead of megablox's) because the no-group rows need the
    same zero-pinning on the backward outputs: dx rows past the last
    group and dw blocks of EMPTY groups are tiles the kernels never
    visit, i.e. uninitialized memory.
    """
    return _gmm(x, w, offsets)


def _vjp_fwd(x, w, offsets):
    return _gmm(x, w, offsets), (x, w, offsets)


def _vjp_bwd(res, g):
    x, w, offsets = res
    b, h = x.shape
    m = w.shape[-1]
    sizes = jnp.diff(offsets).astype(jnp.int32)
    # dx: the grouped product against transposed weights; zero-pinning for
    # no-group rows comes with _gmm
    dx = _gmm(g.astype(x.dtype), jnp.swapaxes(w, 1, 2), offsets)
    # dw[e] = x_e^T @ g_e (the transposed grouped matmul); empty groups'
    # blocks are unvisited -> pin to zero
    tm, tk, tn = _TILING
    dw = _mb.tgmm(
        x.swapaxes(0, 1), g.astype(x.dtype), sizes,
        preferred_element_type=_ACC_DTYPE,
        tiling=(_block(h, tk), _block(b, tm), _block(m, tn)),
        interpret=_interpret(),
    )
    dw = jnp.where(sizes[:, None, None] > 0, dw, 0.0).astype(w.dtype)
    return dx, dw, None


grouped_matmul.defvjp(_vjp_fwd, _vjp_bwd)
