"""Python SDK — the three reference clients rebuilt over this platform:
TrainingClient (kubeflow-training), KatibClient (kubeflow-katib),
KServeClient (kserve)."""

from .client import JobTimeoutError, TrainingClient
from .katib import (
    ExperimentTimeoutError,
    KatibClient,
    search_categorical,
    search_double,
    search_int,
)
from .kserve import IsvcTimeoutError, KServeClient

__all__ = [
    "ExperimentTimeoutError",
    "IsvcTimeoutError",
    "JobTimeoutError",
    "KServeClient",
    "KatibClient",
    "TrainingClient",
    "search_categorical",
    "search_double",
    "search_int",
]
