"""Python SDK."""

from .client import JobTimeoutError, TrainingClient

__all__ = ["JobTimeoutError", "TrainingClient"]
