"""KatibClient — the HPO plane's Python SDK.

Capability parity with the reference's katib SDK [upstream: kubeflow/katib
-> sdk/python/v1beta1 KatibClient]: ``create_experiment``,
``get_experiment``, ``wait_for_experiment_condition``, ``list_trials``,
``get_optimal_hyperparameters``, ``delete_experiment``, and the one-call
``tune()`` UX that builds the Experiment from a search space + objective
and drives JaxJob trials.
"""

from __future__ import annotations

import time
from typing import Optional, Union

from ..api import from_dict, load_yaml
from ..api.experiment import (
    AlgorithmSpec,
    EarlyStoppingSpec,
    Experiment,
    ExperimentSpec,
    FeasibleSpace,
    KIND_EXPERIMENT,
    KIND_TRIAL,
    ObjectiveSpec,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
    Trial,
    TrialTemplate,
)
from ..api.common import ObjectMeta
from ..runtime.platform import LocalPlatform


class ExperimentTimeoutError(TimeoutError):
    pass


def search_double(min: float, max: float, log: bool = False) -> dict:
    """Search-space shorthand: continuous range (`katib.search.double`)."""
    return {"type": ParameterType.DOUBLE, "min": min, "max": max, "log": log}


def search_int(min: int, max: int) -> dict:
    return {"type": ParameterType.INT, "min": min, "max": max}


def search_categorical(values: list) -> dict:
    return {"type": ParameterType.CATEGORICAL, "list": list(values)}


def _param(name: str, spec: dict) -> ParameterSpec:
    ptype = ParameterType(spec["type"])
    if ptype in (ParameterType.DOUBLE, ParameterType.INT):
        fs = FeasibleSpace(min=spec["min"], max=spec["max"],
                           log_scale=bool(spec.get("log", False)))
    else:
        fs = FeasibleSpace(list=spec["list"])
    return ParameterSpec(name=name, parameter_type=ptype, feasible_space=fs)


class KatibClient:
    def __init__(self, platform: LocalPlatform) -> None:
        self.platform = platform

    # -- CRUD -----------------------------------------------------------------

    def create_experiment(
        self, experiment: Union[Experiment, dict, str]
    ) -> Experiment:
        if isinstance(experiment, str):
            objs = load_yaml(experiment)
            if len(objs) != 1 or not isinstance(objs[0], Experiment):
                raise ValueError("expected exactly one Experiment document")
            experiment = objs[0]
        elif isinstance(experiment, dict):
            obj = from_dict(experiment)
            if not isinstance(obj, Experiment):
                raise ValueError(f"manifest is a {obj.kind}, not an Experiment")
            experiment = obj
        created = self.platform.store.create(experiment)
        assert isinstance(created, Experiment)
        return created

    def get_experiment(
        self, name: str, namespace: str = "default"
    ) -> Optional[Experiment]:
        e = self.platform.store.try_get(KIND_EXPERIMENT, name, namespace)
        assert e is None or isinstance(e, Experiment)
        return e

    def delete_experiment(self, name: str, namespace: str = "default") -> None:
        self.platform.store.try_delete(KIND_EXPERIMENT, name, namespace)

    def list_trials(self, name: str, namespace: str = "default") -> list[Trial]:
        return sorted(
            (
                t for t in self.platform.store.list(KIND_TRIAL, namespace)
                if isinstance(t, Trial) and t.spec.experiment_name == name
            ),
            key=lambda t: t.metadata.name,
        )

    # -- waiting / results ----------------------------------------------------

    def wait_for_experiment(
        self, name: str, namespace: str = "default",
        timeout: float = 300.0, poll: float = 0.1,
    ) -> Experiment:
        deadline = time.time() + timeout
        while time.time() < deadline:
            e = self.get_experiment(name, namespace)
            if e is not None and e.status.completed:
                return e
            time.sleep(poll)
        raise ExperimentTimeoutError(
            f"experiment {name}: not completed within {timeout}s")

    def get_optimal_hyperparameters(
        self, name: str, namespace: str = "default"
    ) -> dict:
        """{"value": best objective, "assignments": {param: value}} — the
        reference client's optimal-trial read."""
        e = self.get_experiment(name, namespace)
        if e is None or e.status.current_optimal_value is None:
            return {"value": None, "assignments": {}}
        return {
            "value": e.status.current_optimal_value,
            "trial": e.status.current_optimal_trial,
            "assignments": {
                a.name: a.value for a in e.status.current_optimal_assignments},
        }

    # -- one-call UX ----------------------------------------------------------

    def tune(
        self,
        name: str,
        entrypoint: str,
        parameters: dict[str, dict],
        objective_metric: str = "score",
        objective_type: ObjectiveType = ObjectiveType.MAXIMIZE,
        goal: Optional[float] = None,
        algorithm: str = "random",
        algorithm_settings: Optional[dict[str, str]] = None,
        max_trials: int = 8,
        parallel_trials: int = 2,
        early_stopping: Optional[str] = None,
        num_workers: int = 1,
        base_env: Optional[dict[str, str]] = None,
        namespace: str = "default",
        wait: bool = True,
        timeout: float = 600.0,
    ) -> Experiment:
        """Build + submit an Experiment in one call [reference analog:
        KatibClient.tune].  ``parameters`` maps env-var-ish parameter names
        to search specs (see ``search_double``/``search_int``/
        ``search_categorical``); each trial's JaxJob gets
        ``KFT_<NAME>=${trialParameters.<name>}`` injected.
        """
        env = dict(base_env or {})
        for pname in parameters:
            env[f"KFT_{pname.upper()}"] = "${trialParameters.%s}" % pname
        exp = Experiment(
            metadata=ObjectMeta(name=name, namespace=namespace),
            spec=ExperimentSpec(
                objective=ObjectiveSpec(
                    type=objective_type,
                    objective_metric_name=objective_metric,
                    goal=goal,
                ),
                algorithm=AlgorithmSpec(
                    algorithm_name=algorithm,
                    settings=algorithm_settings or {},
                ),
                parameters=[_param(n, s) for n, s in parameters.items()],
                parallel_trial_count=parallel_trials,
                max_trial_count=max_trials,
                early_stopping=(
                    EarlyStoppingSpec(algorithm_name=early_stopping)
                    if early_stopping else None
                ),
                trial_template=TrialTemplate(job_manifest={
                    "kind": "JaxJob",
                    "metadata": {"name": "placeholder"},
                    "spec": {
                        "replica_specs": {
                            "worker": {
                                "replicas": num_workers,
                                "template": {
                                    "entrypoint": entrypoint,
                                    "env": env,
                                },
                            }
                        }
                    },
                }),
            ),
        )
        created = self.create_experiment(exp)
        if wait:
            return self.wait_for_experiment(name, namespace, timeout=timeout)
        return created
