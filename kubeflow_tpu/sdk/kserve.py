"""KServeClient — the serving plane's Python SDK.

Capability parity with the reference's kserve SDK [upstream: kserve/kserve
-> python/kserve KServeClient]: ``create``, ``get``, ``delete``,
``wait_isvc_ready``, and data-plane calls ``predict``/``explain`` against
the InferenceService's routed URL (V1 protocol).
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Any, Optional, Union

from ..api import from_dict, load_yaml
from ..api.inference import (
    InferenceService,
    InferenceServicePhase,
    KIND_INFERENCE_SERVICE,
)
from ..controlplane.cluster import Cluster


class IsvcTimeoutError(TimeoutError):
    pass


class KServeClient:
    def __init__(self, cluster: Cluster) -> None:
        self.cluster = cluster

    # -- CRUD -----------------------------------------------------------------

    def create(
        self, isvc: Union[InferenceService, dict, str]
    ) -> InferenceService:
        if isinstance(isvc, str):
            objs = load_yaml(isvc)
            if len(objs) != 1 or not isinstance(objs[0], InferenceService):
                raise ValueError("expected exactly one InferenceService document")
            isvc = objs[0]
        elif isinstance(isvc, dict):
            obj = from_dict(isvc)
            if not isinstance(obj, InferenceService):
                raise ValueError(f"manifest is a {obj.kind}, not an InferenceService")
            isvc = obj
        created = self.cluster.store.create(isvc)
        assert isinstance(created, InferenceService)
        return created

    def get(
        self, name: str, namespace: str = "default"
    ) -> Optional[InferenceService]:
        isvc = self.cluster.store.try_get(KIND_INFERENCE_SERVICE, name, namespace)
        assert isvc is None or isinstance(isvc, InferenceService)
        return isvc

    def delete(self, name: str, namespace: str = "default") -> None:
        self.cluster.store.try_delete(KIND_INFERENCE_SERVICE, name, namespace)

    # -- waiting --------------------------------------------------------------

    def wait_isvc_ready(
        self, name: str, namespace: str = "default",
        timeout: float = 120.0, poll: float = 0.1,
    ) -> InferenceService:
        deadline = time.time() + timeout
        isvc = None
        while time.time() < deadline:
            isvc = self.get(name, namespace)
            if isvc is not None:
                if isvc.status.phase == InferenceServicePhase.READY:
                    return isvc
                if isvc.status.phase == InferenceServicePhase.FAILED:
                    raise RuntimeError(
                        f"InferenceService {name} failed: {isvc.status.message}")
            time.sleep(poll)
        raise IsvcTimeoutError(
            f"InferenceService {name}: not Ready within {timeout}s "
            f"(last: {isvc.status if isvc else None})")

    # -- canary rollout (KServe canaryTrafficPercent verbs) -------------------

    def _update_spec(self, name: str, namespace: str, mut) -> InferenceService:
        from ..api.inference import KIND_INFERENCE_SERVICE as KIND

        def apply(o):
            assert isinstance(o, InferenceService)
            mut(o)

        out = self.cluster.store.update_with_retry(KIND, name, namespace, apply)
        assert isinstance(out, InferenceService)
        return out

    def rollout(
        self, name: str, spec_update: dict, traffic_percent: int,
        namespace: str = "default",
    ) -> InferenceService:
        """Deploy a spec change as a canary at ``traffic_percent``%; the
        current revision keeps serving the rest.  ``spec_update`` is a
        partial spec dict merged over the current one (e.g.
        ``{"predictor": {...}}`` replaces the predictor)."""
        from ..api.inference import InferenceServiceSpec

        def mut(o: InferenceService) -> None:
            merged = o.spec.model_dump(mode="json")
            merged.update(spec_update)
            merged["canary_traffic_percent"] = traffic_percent
            o.spec = InferenceServiceSpec.model_validate(merged)

        return self._update_spec(name, namespace, mut)

    def promote(self, name: str, namespace: str = "default") -> InferenceService:
        """Roll the canary revision out fully (it becomes the stable
        revision; the old one drains)."""
        def mut(o: InferenceService) -> None:
            o.spec.canary_traffic_percent = None

        return self._update_spec(name, namespace, mut)

    def rollback(self, name: str, namespace: str = "default") -> InferenceService:
        """Abandon the canary: restore the stable revision's spec (recorded
        in status.stable_spec by the controller)."""
        from ..api.inference import InferenceServiceSpec

        isvc = self.get(name, namespace)
        if isvc is None:
            raise RuntimeError(f"InferenceService {name} not found")
        if not isvc.status.stable_spec:
            raise RuntimeError(f"InferenceService {name} has no recorded stable spec")
        restored = InferenceServiceSpec.model_validate(isvc.status.stable_spec)

        def mut(o: InferenceService) -> None:
            o.spec = restored.model_copy(deep=True)
            o.spec.canary_traffic_percent = None

        return self._update_spec(name, namespace, mut)

    # -- data plane (V1 protocol) ---------------------------------------------

    def _post(self, url: str, payload: dict, timeout: float) -> dict:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())

    def _routed(self, name: str, namespace: str) -> str:
        isvc = self.get(name, namespace)
        if isvc is None or not isvc.status.url:
            raise RuntimeError(f"InferenceService {name} has no routed URL")
        return isvc.status.url

    def predict(
        self, name: str, instances: list[Any],
        namespace: str = "default", timeout: float = 60.0,
    ) -> list[Any]:
        url = self._routed(name, namespace)
        out = self._post(
            f"{url}/v1/models/{name}:predict", {"instances": instances}, timeout)
        return out["predictions"]

    def explain(
        self, name: str, instances: list[Any],
        namespace: str = "default", timeout: float = 120.0,
    ) -> list[Any]:
        url = self._routed(name, namespace)
        out = self._post(
            f"{url}/v1/models/{name}:explain", {"instances": instances}, timeout)
        return out["explanations"]
