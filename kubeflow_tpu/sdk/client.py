"""TrainingClient — the Python SDK over the control plane.

Capability parity with the reference SDK [upstream:
kubeflow/training-operator -> sdk/python/kubeflow/training/api/
training_client.py]: ``create_job``, ``get_job``, ``wait_for_job_conditions``,
``get_job_logs``, ``delete_job``, and the one-call ``train()`` UX (the v1.9
LLM fine-tune entry named in the north star — here it emits a JaxJob whose
pods run a packaged JAX trainer instead of a torch/peft container).
"""

from __future__ import annotations

import time
from typing import Optional, Sequence, Union

from ..api import (
    Container,
    JaxJob,
    ObjectMeta,
    ReplicaSpec,
    Resources,
    RestartPolicy,
    RunPolicy,
    from_dict,
    load_yaml,
)
from ..api.common import JobConditionType, has_condition, replica_pod_name
from ..api.jaxjob import KIND_JAXJOB, WORKER
from ..runtime.platform import LocalPlatform


class JobTimeoutError(TimeoutError):
    pass


class TrainingClient:
    def __init__(self, platform: LocalPlatform) -> None:
        self.platform = platform

    # -- CRUD -----------------------------------------------------------------

    def create_job(self, job: Union[JaxJob, dict, str]) -> JaxJob:
        if isinstance(job, str):
            objs = load_yaml(job)
            if len(objs) != 1 or not isinstance(objs[0], JaxJob):
                raise ValueError("expected exactly one JaxJob document")
            job = objs[0]
        elif isinstance(job, dict):
            obj = from_dict(job)
            if not isinstance(obj, JaxJob):
                raise ValueError(f"manifest is a {obj.kind}, not a JaxJob")
            job = obj
        created = self.platform.store.create(job)
        assert isinstance(created, JaxJob)
        return created

    def get_job(self, name: str, namespace: str = "default") -> Optional[JaxJob]:
        job = self.platform.store.try_get(KIND_JAXJOB, name, namespace)
        assert job is None or isinstance(job, JaxJob)
        return job

    def delete_job(self, name: str, namespace: str = "default") -> None:
        self.platform.store.try_delete(KIND_JAXJOB, name, namespace)

    def list_jobs(self, namespace: Optional[str] = None) -> list[JaxJob]:
        return [j for j in self.platform.store.list(KIND_JAXJOB, namespace)]  # type: ignore[misc]

    # -- waiting / logs -------------------------------------------------------

    def wait_for_job_conditions(
        self,
        name: str,
        namespace: str = "default",
        expected: Sequence[JobConditionType] = (JobConditionType.SUCCEEDED,),
        timeout: float = 120.0,
        poll: float = 0.05,
    ) -> JaxJob:
        """Block until the job reaches one of ``expected``; raises on FAILED
        unless FAILED is itself expected (the reference SDK's semantics)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            job = self.get_job(name, namespace)
            if job is not None:
                for c in expected:
                    if has_condition(job.status.conditions, c):
                        return job
                if JobConditionType.FAILED not in expected and has_condition(
                    job.status.conditions, JobConditionType.FAILED
                ):
                    raise RuntimeError(
                        f"job {name} failed: "
                        + "; ".join(
                            f"{c.reason}: {c.message}" for c in job.status.conditions
                        )
                    )
            time.sleep(poll)
        raise JobTimeoutError(f"job {name}: no {list(expected)} within {timeout}s")

    def get_job_logs(
        self, name: str, namespace: str = "default"
    ) -> dict[str, str]:
        """Pod name -> captured stdout/stderr (the kubectl-logs surface)."""
        out: dict[str, str] = {}
        job = self.get_job(name, namespace)
        if job is None:
            return out
        for rtype, rspec in job.spec.replica_specs.items():
            for idx in range(rspec.replicas):
                pod_name = replica_pod_name(name, rtype, idx)
                path = self.platform.kubelet.pod_log_path(namespace, pod_name)
                try:
                    with open(path) as f:
                        out[pod_name] = f.read()
                except OSError:
                    pass
        return out

    # -- one-call UX ----------------------------------------------------------

    def train(
        self,
        name: str,
        entrypoint: str,
        num_workers: int = 1,
        chips_per_worker: int = 0,
        env: Optional[dict[str, str]] = None,
        mesh: Optional[dict[str, int]] = None,
        model: Optional[str] = None,
        lora_rank: int = 0,
        publish_to: Optional[str] = None,
        backoff_limit: int = 0,
        namespace: str = "default",
        wait: bool = True,
        timeout: float = 300.0,
    ) -> JaxJob:
        """Build + submit a JaxJob in one call [reference analog:
        TrainingClient.train, the north-star fine-tune UX].

        ``model``: pretrained snapshot URI (``hf://org/name[@rev]`` or
        ``file:///path``) to fine-tune from — the literal v1.9 LLM path:
        the trainer resolves it through the storage initializer, takes the
        architecture from the snapshot's config.json, and loads the
        weights before step 0 (train/llm.py KFT_INIT_FROM).

        ``lora_rank``: > 0 trains rank-r LoRA adapters on the snapshot's
        q/v projections with the base FROZEN (the reference's peft path,
        SURVEY §3.5) — checkpoints and the published artifact shrink to
        adapter size.  ``publish_to``: directory the coordinator writes
        the trained snapshot to (save_adapter under LoRA, save_pretrained
        otherwise) — the train -> publish -> serve loop's publish step.
        """
        if model:
            env = {**(env or {}), "KFT_INIT_FROM": model}
        if lora_rank:
            env = {**(env or {}), "KFT_LORA_RANK": str(int(lora_rank))}
        if publish_to:
            env = {**(env or {}), "KFT_PUBLISH_TO": publish_to}
        job = JaxJob(
            metadata=ObjectMeta(name=name, namespace=namespace),
            spec={
                # coordinator_port defaults to 0 = allocated by the
                # controller at gang-bind time (r1 weak #6)
                "run_policy": RunPolicy(backoff_limit=backoff_limit),
                **({"mesh": mesh} if mesh else {}),
                "replica_specs": {
                    WORKER: ReplicaSpec(
                        replicas=num_workers,
                        restart_policy=RestartPolicy.EXIT_CODE,
                        template=Container(
                            entrypoint=entrypoint,
                            env=env or {},
                            resources=Resources(tpu=chips_per_worker),
                        ),
                    )
                },
            },
        )
        created = self.create_job(job)
        if wait:
            return self.wait_for_job_conditions(name, namespace, timeout=timeout)
        return created
