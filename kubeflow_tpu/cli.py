"""kft — the kubectl-style CLI over the REST API server.

The reference's whole UX runs through kubectl verbs against CRDs (every
SURVEY §3 call stack starts at ``kubectl apply``); this is that surface
for the TPU platform, talking HTTP to ``controlplane/apiserver.py``:

    kft --server URL apply -f job.yaml      # create-or-update (multi-doc)
    kft get jaxjobs [-n ns] [-o yaml|json]
    kft get isvc my-svc
    kft describe jaxjob demo                # object + events
    kft delete trial demo-t0001
    kft logs demo-worker-0
    kft api-resources

The server URL comes from ``--server`` or ``$KFT_SERVER`` (a cluster
started with ``Cluster.serve_api()`` prints it).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Optional

import yaml


class CliError(RuntimeError):
    """API error carrying the server's structured ``reason`` (and, for
    watch-cursor expiry, the server's resync ``cursor``) — callers branch
    on ``reason``, never on message text."""

    def __init__(self, msg: str, reason: str = "", cursor: Optional[int] = None):
        super().__init__(msg)
        self.reason = reason
        self.cursor = cursor


#: bearer token attached to every request (set by main() from --token /
#: $KFT_TOKEN; the apiserver's single-admin-credential authn)
_TOKEN: Optional[str] = None


def _request(method: str, url: str, body: Optional[dict] = None) -> Any:
    data = json.dumps(body).encode() if body is not None else None
    headers = {"Content-Type": "application/json"}
    if _TOKEN:
        headers["Authorization"] = f"Bearer {_TOKEN}"
    req = urllib.request.Request(
        url, data=data, method=method, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            raw = resp.read()
            ctype = resp.headers.get("Content-Type", "")
            return raw.decode() if "text/plain" in ctype else json.loads(raw)
    except urllib.error.HTTPError as e:
        reason, cursor = "", None
        try:
            payload = json.loads(e.read())
            msg = payload.get("error", str(e))
            reason = payload.get("reason", "")
            cursor = payload.get("cursor")
        except Exception:  # noqa: BLE001 — unparseable error body:
            # fall back to the raw HTTPError text
            msg = str(e)
        raise CliError(f"{method} {url}: {msg}", reason=reason,
                       cursor=cursor) from None
    except OSError as e:
        raise CliError(f"cannot reach API server at {url}: {e}") from None


def _phase_of(obj: dict) -> str:
    st = obj.get("status", {}) or {}
    if st.get("phase"):
        return str(st["phase"])
    conds = st.get("conditions") or []
    return str(conds[-1].get("type", "")) if conds else ""


def _age(obj: dict) -> str:
    ts = (obj.get("metadata", {}) or {}).get("creationTimestamp") or (
        obj.get("metadata", {}) or {}).get("creation_timestamp")
    if not ts:
        return ""
    try:
        s = int(time.time() - float(ts))
    except (TypeError, ValueError):
        return ""
    if s < 120:
        return f"{s}s"
    if s < 7200:
        return f"{s // 60}m"
    return f"{s // 3600}h"


def cmd_apply(server: str, args) -> int:
    with (sys.stdin if args.filename == "-" else open(args.filename)) as f:
        docs = [d for d in yaml.safe_load_all(f.read()) if d]
    for doc in docs:
        kind = doc.get("kind")
        if not kind:
            raise CliError("manifest document has no 'kind'")
        name = (doc.get("metadata") or {}).get("name", "?")
        ns = (doc.get("metadata") or {}).get("namespace", "default")
        try:
            _request("POST", f"{server}/apis/{kind}", doc)
            print(f"{kind.lower()}/{name} created")
        except CliError as e:
            # branch on the server's structured reason: a 422 admission
            # rejection whose MESSAGE contains "exists" must surface
            # as-is, not trigger a confusing GET+PUT
            if e.reason != "AlreadyExists":
                raise
            # create-or-update: refresh spec onto the live object (kubectl
            # apply semantics, optimistic concurrency handled by re-read)
            cur = _request("GET", f"{server}/apis/{kind}/{ns}/{name}")
            cur["spec"] = doc.get("spec", cur.get("spec"))
            _request("PUT", f"{server}/apis/{kind}/{ns}/{name}", cur)
            print(f"{kind.lower()}/{name} configured")
    return 0


def cmd_get(server: str, args) -> int:
    if getattr(args, "watch", False):
        return _watch_loop(server, args)
    if args.name:
        obj = _request(
            "GET", f"{server}/apis/{args.kind}/{args.namespace}/{args.name}")
        items = [obj]
    else:
        url = f"{server}/apis/{args.kind}"
        if args.namespace != "_all":
            url += f"?namespace={args.namespace}"
        items = _request("GET", url)["items"]
    if args.output == "json":
        print(json.dumps(items if not args.name else items[0], indent=1))
        return 0
    if args.output == "yaml":
        print(yaml.safe_dump_all(items, sort_keys=False), end="")
        return 0
    rows = [("NAMESPACE", "NAME", "PHASE", "AGE")]
    for o in items:
        md = o.get("metadata", {}) or {}
        rows.append((md.get("namespace", ""), md.get("name", ""),
                     _phase_of(o), _age(o)))
    widths = [max(len(r[i]) for r in rows) for i in range(4)]
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
    return 0


def _watch_loop(server: str, args) -> int:
    """kubectl get -w: stream events for a kind until --watch-seconds
    elapses, resuming between long-polls with the server's cursor (no
    events are lost between polls)."""
    deadline = time.time() + args.watch_seconds
    cursor = 0
    while time.time() < deadline:
        poll = max(1.0, min(30.0, deadline - time.time()))
        try:
            out = _request(
                "GET",
                f"{server}/apis/{args.kind}?watch=true&timeout={poll}"
                f"&cursor={cursor}&namespace={args.namespace}"
                if args.namespace != "_all"
                else f"{server}/apis/{args.kind}?watch=true&timeout={poll}"
                     f"&cursor={cursor}",
            )
        except CliError as e:
            if e.reason != "Expired" or e.cursor is None:
                raise
            # 410 Gone: the buffer rolled past our cursor — announce the
            # gap and resync to the server's current cursor (the kubectl
            # relist-and-rewatch analog)
            print(f"WATCH-RESYNC\t(events lost; resuming at {e.cursor})",
                  flush=True)
            cursor = e.cursor
            continue
        cursor = out["cursor"]
        for ev in out["items"]:
            md = ev["object"].get("metadata", {}) or {}
            print(f"{ev['type']}	{md.get('namespace', '')}/"
                  f"{md.get('name', '')}	{_phase_of(ev['object'])}",
                  flush=True)
    return 0


def cmd_describe(server: str, args) -> int:
    obj = _request(
        "GET", f"{server}/apis/{args.kind}/{args.namespace}/{args.name}")
    print(yaml.safe_dump(obj, sort_keys=False), end="")
    events = _request(
        "GET",
        f"{server}/apis/{args.kind}/{args.namespace}/{args.name}/events",
    )["items"]
    print("Events:")
    if not events:
        print("  <none>")
    for e in events:
        print(f"  {e.get('type', '')}\t{e.get('reason', '')}\t"
              f"{e.get('message', '')}")
    return 0


def cmd_delete(server: str, args) -> int:
    _request(
        "DELETE", f"{server}/apis/{args.kind}/{args.namespace}/{args.name}")
    print(f"{args.kind.lower()}/{args.name} deleted")
    return 0


def cmd_logs(server: str, args) -> int:
    out = _request(
        "GET", f"{server}/apis/Pod/{args.namespace}/{args.name}/logs")
    print(out, end="" if str(out).endswith("\n") else "\n")
    return 0


def cmd_api_resources(server: str, args) -> int:
    for kind in _request("GET", f"{server}/apis")["kinds"]:
        print(kind)
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kft", description="kubectl-style CLI for the TPU platform")
    p.add_argument("--server", default=os.environ.get("KFT_SERVER"),
                   help="API server URL (or $KFT_SERVER)")
    p.add_argument("--token", default=os.environ.get("KFT_TOKEN"),
                   help="bearer token for a token-protected API server "
                        "(or $KFT_TOKEN)")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("apply", help="create or update from a manifest")
    sp.add_argument("-f", "--filename", required=True)
    sp.set_defaults(fn=cmd_apply)

    for verb, fn in (("get", cmd_get),):
        sp = sub.add_parser(verb)
        sp.add_argument("kind")
        sp.add_argument("name", nargs="?")
        sp.add_argument("-n", "--namespace", default="default")
        sp.add_argument("-A", "--all-namespaces", dest="namespace",
                        action="store_const", const="_all")
        sp.add_argument("-o", "--output", choices=("table", "yaml", "json"),
                        default="table")
        sp.add_argument("-w", "--watch", action="store_true",
                        help="stream events for this kind")
        sp.add_argument("--watch-seconds", type=float, default=30.0)
        sp.set_defaults(fn=fn)

    for verb, fn in (("describe", cmd_describe), ("delete", cmd_delete)):
        sp = sub.add_parser(verb)
        sp.add_argument("kind")
        sp.add_argument("name")
        sp.add_argument("-n", "--namespace", default="default")
        sp.set_defaults(fn=fn)

    sp = sub.add_parser("logs", help="pod stdout/stderr")
    sp.add_argument("name")
    sp.add_argument("-n", "--namespace", default="default")
    sp.set_defaults(fn=cmd_logs)

    sp = sub.add_parser("api-resources", help="list served kinds")
    sp.set_defaults(fn=cmd_api_resources)
    return p


def main(argv: Optional[list[str]] = None) -> int:
    global _TOKEN
    args = build_parser().parse_args(argv)
    _TOKEN = args.token
    if not args.server:
        print("kft: no API server (--server or $KFT_SERVER)", file=sys.stderr)
        return 2
    try:
        return args.fn(args.server.rstrip("/"), args)
    except CliError as e:
        print(f"kft: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
